"""Mesh construction and sharding specs for AL state and packed forests."""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_active_learning_tpu.runtime.state import PoolState

AXIS_DATA = "data"
AXIS_MODEL = "model"


def make_mesh(
    data: Optional[int] = None,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(data, model)`` mesh over the available devices.

    Defaults to all devices on the data axis — the shape of the problem: pools
    are huge, forests are small (the reference likewise distributes the pool
    and keeps trees on the driver, ``active_learner.py:169-184``).
    """
    devs = list(devices if devices is not None else jax.devices())
    if data is None:
        if len(devs) % model:
            raise ValueError(f"{len(devs)} devices not divisible by model={model}")
        data = len(devs) // model
    if data * model > len(devs):
        raise ValueError(f"mesh {data}x{model} exceeds {len(devs)} devices")
    grid = np.asarray(devs[: data * model]).reshape(data, model)
    return Mesh(grid, (AXIS_DATA, AXIS_MODEL))


def pool_spec() -> P:
    """Pool rows sharded over data; feature dim replicated."""
    return P(AXIS_DATA, None)


def mask_spec() -> P:
    return P(AXIS_DATA)


def forest_spec() -> P:
    """Trees sharded over the model axis; node arrays replicated per tree."""
    return P(AXIS_MODEL, None)


def replicated_spec() -> P:
    return P()


@functools.lru_cache(maxsize=16)
def _resharder(sharding: NamedSharding):
    """One cached jitted identity per target sharding — a fresh lambda per
    call would retrace and recompile on every forest leaf every round.
    Bounded (unlike ``functools.cache``): the key retains the mesh and its
    compiled executable, and test suites construct many meshes."""
    return jax.jit(lambda a: a, out_shardings=sharding)


def global_put(x, mesh: Mesh, spec: P):
    """Place ``x`` with ``spec`` on ``mesh``, working for MULTI-PROCESS meshes
    too. ``jax.device_put`` only accepts fully-addressable shardings; when the
    mesh spans processes each process holds the same logical value (the
    multi-controller model), so the global array is assembled per-process via
    ``make_array_from_callback`` — every process contributes exactly its
    addressable shards. Typed PRNG keys ride as their uint32 key data.
    """
    sharding = NamedSharding(mesh, spec)
    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    if isinstance(x, jax.Array) and getattr(x, "sharding", None) == sharding:
        return x
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        # Already a global array (e.g. a device-fit forest): reshard inside
        # jit — host round-trips are impossible for non-addressable data.
        return _resharder(sharding)(x)
    if jnp.issubdtype(getattr(x, "dtype", None), jax.dtypes.prng_key):
        data = np.asarray(jax.random.key_data(x))
        impl = jax.random.key_impl(x)
        # key data carries a trailing impl axis the logical spec doesn't name
        dspec = P(*(tuple(spec) + (None,)))
        dsharding = NamedSharding(mesh, dspec)
        global_data = jax.make_array_from_callback(
            data.shape, dsharding, lambda idx: data[idx]
        )
        return jax.random.wrap_key_data(global_data, impl=impl)
    arr = np.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])


def shard_fill_watermark(
    n_filled: jnp.ndarray, n_pool: int, n_shards: int
) -> jnp.ndarray:
    """Split a global scalar fill watermark into the per-shard ``[S]`` leaf.

    Shard ``s`` owns the contiguous row block ``[s * rows, (s + 1) * rows)``
    (``rows = n_pool // n_shards``); a contiguously-filled pool therefore
    fills shard ``s`` to ``clip(n_filled - s * rows, 0, rows)``. The masks
    this leaf induces (``PoolState.fill_mask``) are identical to the scalar's
    — pinned by the parity test — while each shard now owns its own
    watermark, so per-shard ingest can advance it without a global
    renumbering and the global view is the psum'd sum
    (``runtime.state.filled_count``).
    """
    rows = n_pool // n_shards
    base = jnp.arange(n_shards, dtype=jnp.int32) * rows
    return jnp.clip(jnp.asarray(n_filled, jnp.int32) - base, 0, rows)


def shard_pool_state(state: PoolState, mesh: Mesh) -> PoolState:
    """Place pool arrays with rows sharded over the data axis.

    Pool sizes not divisible by the axis must be padded first with
    :func:`runtime.state.pad_for_sharding` (``run_experiment`` does this when
    a >1-device mesh is configured); this function raises otherwise rather
    than let a shard_map kernel fail with an opaque block-shape error.

    A scalar ``n_filled`` watermark becomes the per-shard ``[S]`` leaf placed
    ``P(data)`` (:func:`shard_fill_watermark`) — replicating the scalar
    (the pre-pod behavior) left every shard consulting a GLOBAL watermark
    that goes stale the moment one shard ingests on its own. An already
    per-shard leaf is validated against the mesh and re-placed as-is.
    """
    n = state.n_pool
    data_axis = mesh.shape[AXIS_DATA]
    if n % data_axis:
        raise ValueError(
            f"pool size {n} not divisible by data axis {data_axis}; call "
            "runtime.state.pad_for_sharding first"
        )
    n_filled = state.n_filled
    if n_filled is not None:
        n_filled = jnp.asarray(n_filled)
        if n_filled.ndim == 0:
            n_filled = shard_fill_watermark(n_filled, n, data_axis)
        elif n_filled.shape != (data_axis,):
            raise ValueError(
                f"per-shard n_filled leaf {n_filled.shape} does not match "
                f"the data axis ({data_axis} shards)"
            )
    return state.replace(
        x=global_put(state.x, mesh, pool_spec()),
        oracle_y=global_put(state.oracle_y, mesh, mask_spec()),
        labeled_mask=global_put(state.labeled_mask, mesh, mask_spec()),
        key=global_put(state.key, mesh, replicated_spec()),
        round=global_put(state.round, mesh, replicated_spec()),
        n_filled=(
            None
            if n_filled is None
            else global_put(n_filled, mesh, P(AXIS_DATA))
        ),
    )


def forest_tree_specs(forest):
    """Per-leaf PartitionSpecs sharding a forest's tree axis over ``model``.

    The one source of the "tree axis first, rest replicated" rule — used both
    to place forests (:func:`shard_forest`) and as ``shard_map`` in_specs
    (``parallel.kernels.sharded_votes``). Every array field of every
    representation (gather ``PackedForest``, path-matrix ``GemmForest``,
    fused ``PallasForest``) carries the tree axis first.
    """
    return jax.tree.map(
        lambda leaf: P(AXIS_MODEL, *([None] * (leaf.ndim - 1))), forest
    )


def shard_forest(forest, mesh: Mesh):
    """Place a forest with trees sharded over the model axis."""
    specs = forest_tree_specs(forest)
    return jax.tree.map(
        lambda leaf, spec: global_put(leaf, mesh, spec),
        forest,
        specs,
    )


def constrain_forest(forest, mesh: Mesh):
    """Traced twin of :func:`shard_forest` for forests built INSIDE a jitted
    program (the chunked driver's in-scan device fit, runtime/loop.py
    ``make_chunk_fn``): ``device_put`` is a host-side placement, so inside a
    ``lax.scan`` body the model-axis layout is asserted with
    ``with_sharding_constraint`` instead — same specs, same resulting
    placement, but expressed as a constraint GSPMD propagates through the
    scan. Works on tracers and concrete arrays alike.
    """
    specs = forest_tree_specs(forest)
    return jax.tree.map(
        lambda leaf, spec: jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, spec)
        ),
        forest,
        specs,
    )
