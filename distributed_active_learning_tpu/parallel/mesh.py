"""Mesh construction and sharding specs for AL state and packed forests."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_active_learning_tpu.runtime.state import PoolState

AXIS_DATA = "data"
AXIS_MODEL = "model"


def make_mesh(
    data: Optional[int] = None,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(data, model)`` mesh over the available devices.

    Defaults to all devices on the data axis — the shape of the problem: pools
    are huge, forests are small (the reference likewise distributes the pool
    and keeps trees on the driver, ``active_learner.py:169-184``).
    """
    devs = list(devices if devices is not None else jax.devices())
    if data is None:
        if len(devs) % model:
            raise ValueError(f"{len(devs)} devices not divisible by model={model}")
        data = len(devs) // model
    if data * model > len(devs):
        raise ValueError(f"mesh {data}x{model} exceeds {len(devs)} devices")
    grid = np.asarray(devs[: data * model]).reshape(data, model)
    return Mesh(grid, (AXIS_DATA, AXIS_MODEL))


def pool_spec() -> P:
    """Pool rows sharded over data; feature dim replicated."""
    return P(AXIS_DATA, None)


def mask_spec() -> P:
    return P(AXIS_DATA)


def forest_spec() -> P:
    """Trees sharded over the model axis; node arrays replicated per tree."""
    return P(AXIS_MODEL, None)


def replicated_spec() -> P:
    return P()


def shard_pool_state(state: PoolState, mesh: Mesh) -> PoolState:
    """Place pool arrays with rows sharded over the data axis.

    Pool sizes not divisible by the axis must be padded first with
    :func:`runtime.state.pad_for_sharding` (``run_experiment`` does this when
    a >1-device mesh is configured); this function raises otherwise rather
    than let a shard_map kernel fail with an opaque block-shape error.
    """
    n = state.n_pool
    data_axis = mesh.shape[AXIS_DATA]
    if n % data_axis:
        raise ValueError(
            f"pool size {n} not divisible by data axis {data_axis}; call "
            "runtime.state.pad_for_sharding first"
        )
    return state.replace(
        x=jax.device_put(state.x, NamedSharding(mesh, pool_spec())),
        oracle_y=jax.device_put(state.oracle_y, NamedSharding(mesh, mask_spec())),
        labeled_mask=jax.device_put(state.labeled_mask, NamedSharding(mesh, mask_spec())),
        key=jax.device_put(state.key, NamedSharding(mesh, replicated_spec())),
        round=jax.device_put(state.round, NamedSharding(mesh, replicated_spec())),
    )


def forest_tree_specs(forest):
    """Per-leaf PartitionSpecs sharding a forest's tree axis over ``model``.

    The one source of the "tree axis first, rest replicated" rule — used both
    to place forests (:func:`shard_forest`) and as ``shard_map`` in_specs
    (``parallel.kernels.sharded_votes``). Every array field of every
    representation (gather ``PackedForest``, path-matrix ``GemmForest``,
    fused ``PallasForest``) carries the tree axis first.
    """
    return jax.tree.map(
        lambda leaf: P(AXIS_MODEL, *([None] * (leaf.ndim - 1))), forest
    )


def shard_forest(forest, mesh: Mesh):
    """Place a forest with trees sharded over the model axis."""
    specs = forest_tree_specs(forest)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        forest,
        specs,
    )
