"""Multi-host (DCN) initialization for pools larger than one slice.

The reference scales out with Spark executors over TCP (SURVEY.md §5.8); the
TPU-native equivalent is ``jax.distributed``: every host runs the SAME
program, ``jax.devices()`` spans all hosts after initialization, and the
meshes built by :func:`parallel.mesh.make_mesh` simply cover more devices —
XLA routes collectives over ICI within a slice and DCN across slices. No
other code changes: the AL round, the shard_map kernels, and GSPMD neural
training are already written against a mesh of arbitrary size.

Host-side responsibilities under multi-host SPMD:

- every process must execute the same jitted computations in the same order
  (the driver loop in ``runtime/loop.py`` is already deterministic given the
  config);
- host-only steps (sklearn fit, oracle reveal logging) run identically on
  each process from the same seed, so no cross-host coordination is needed
  beyond the jax.distributed barrier at init;
- checkpoints should be written by process 0 only (``is_primary``).

Evidence (r4): ``tests/test_multihost_2proc.py`` runs a
collective/primary-checkpoint probe AND full AL experiments on BOTH loops
over a real 2-process global mesh — GSPMD compiles the fused forest round
and the neural fit/MC-acquire programs into SPMD programs spanning the
processes, curves match the single-process runs exactly, and per-round
checkpoints gather collectively with primary-only writes (host arrays
enter through ``parallel.mesh.global_put``, which builds global arrays
for non-addressable shardings; host round-trips go through
:func:`host_np`).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_ENV_COORD = "JAX_COORDINATOR_ADDRESS"
_ENV_NPROC = "JAX_NUM_PROCESSES"
_ENV_PID = "JAX_PROCESS_ID"


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host job (wrapper over ``jax.distributed.initialize``).

    Arguments default to the ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES``
    / ``JAX_PROCESS_ID`` environment variables (this launcher's contract —
    resolved here because ``jax.distributed.initialize`` reads the count/id
    only from cluster-specific detectors); on Cloud TPU pods all three are
    auto-detected by jax itself and may be left unset entirely.
    """
    if coordinator_address is None:
        coordinator_address = os.environ.get(_ENV_COORD)
    if num_processes is None and os.environ.get(_ENV_NPROC) is not None:
        num_processes = int(os.environ[_ENV_NPROC])
    if process_id is None and os.environ.get(_ENV_PID) is not None:
        process_id = int(os.environ[_ENV_PID])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def maybe_initialize() -> bool:
    """Initialize iff a multi-host launch is configured; returns whether it was.

    Two launch contracts engage it: the explicit env trio
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``), and
    Cloud TPU pods, where the runtime auto-detects everything but still needs
    ``jax.distributed.initialize()`` *called* — detected here via the pod
    metadata env (multiple entries in ``TPU_WORKER_HOSTNAMES``). Single-host
    runs skip initialization: calling it there would start a coordination
    service nothing connects to.
    """
    nproc = os.environ.get(_ENV_NPROC)
    if nproc is not None and int(nproc) <= 1:
        # Explicit single-process override: lets a pod worker run standalone
        # (debug runs, --list) without blocking at the distributed barrier.
        return False
    if os.environ.get(_ENV_COORD) is not None and nproc is not None:
        initialize()
        return True
    # Cloud TPU pod: worker hostnames are provisioned into the env; >1 worker
    # means multi-host, and initialize() auto-detects coordinator/count/id.
    workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len(workers.split(",")) > 1:
        initialize()
        return True
    return False


def is_primary() -> bool:
    """True on the process that should own host-side writes (checkpoints,
    results logs)."""
    return jax.process_index() == 0


def process_count() -> int:
    return jax.process_count()


def gather_scalar_gauges(values: dict) -> dict:
    """Allgather a dict of per-host scalar gauges -> ``{name: [v_host0, ...]}``.

    COLLECTIVE when the job spans processes — every process must call it at
    the same point with the same key set (the telemetry touchdowns are
    symmetric across processes, same as the checkpoint gathers). Single-
    process runs return one-element lists without touching any collective.
    Used by :class:`runtime.telemetry.MetricsWriter` so the primary-only
    JSONL stream still records every host's gauges.
    """
    names = sorted(values)
    if jax.process_count() <= 1:
        return {n: [float(values[n])] for n in names}
    import numpy as np
    from jax.experimental import multihost_utils

    local = np.asarray([float(values[n]) for n in names], dtype=np.float64)
    gathered = np.asarray(
        multihost_utils.process_allgather(local)
    ).reshape(jax.process_count(), len(names))
    return {n: [float(v) for v in gathered[:, i]] for i, n in enumerate(names)}


def host_np(x):
    """``np.asarray`` that also works for global arrays spanning processes.

    Fully-addressable (single-process) and fully-replicated global arrays
    convert directly; a data-sharded multi-process array is allgathered
    first. COLLECTIVE in that case — every process must call it at the same
    point (the loop's host round-trips are symmetric across processes, which
    is what makes this safe).
    """
    import numpy as np

    if (
        isinstance(x, jax.Array)
        and not x.is_fully_addressable
        and not x.sharding.is_fully_replicated
    ):
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)
