"""Distribution layer: device meshes, shardings, collectives, sharded kernels.

Replaces the reference's L1 Spark runtime (SURVEY.md §2.4): RDD partitions
become mesh-axis shards of dense arrays, shuffle joins become XLA collectives
over ICI (``psum``/``all_gather``), and the driver/executor split disappears
into one SPMD program. Multi-host scaling goes through ``jax.distributed`` +
the same mesh over DCN (no code change — the mesh just spans more devices).

Axes:
  ``data``  — pool rows (the reference's RDD partitioning of the unlabeled pool)
  ``model`` — ensemble/tree axis (the reference's sequential per-tree jobs,
              ``classes/active_learner.py:169-184``, become a sharded vmap)
"""

from distributed_active_learning_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    make_mesh,
    pool_spec,
    forest_spec,
    replicated_spec,
    shard_pool_state,
    shard_forest,
    constrain_forest,
)
from distributed_active_learning_tpu.parallel.kernels import (
    sharded_votes,
    sharded_similarity_mass,
    make_sharded_round_fn,
)
from distributed_active_learning_tpu.parallel.collectives import (
    vector_accumulate,
    masked_mean,
    gather_fills,
    exchange_blocks,
)
from distributed_active_learning_tpu.parallel.multihost import (
    maybe_initialize,
    is_primary,
    process_count,
)
