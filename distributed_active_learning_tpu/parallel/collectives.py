"""Named-axis collective helpers.

The reference defines a driver-side vector accumulator
(``final_thesis/vector_accum.py:4-11``: elementwise vector add with
``zero``/``addInPlace``) that is imported but never invoked — the idea it
gestures at (aggregate per-partition vectors without a shuffle) is exactly what
``lax.psum`` over a mesh axis does, riding ICI instead of the Spark driver.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def vector_accumulate(local: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Elementwise sum of per-shard vectors over ``axis_name``.

    The working realization of ``VectorAccumulatorParam.addInPlace``
    (``vector_accum.py:8-11``) as an ICI all-reduce.
    """
    return lax.psum(local, axis_name)


def global_count(local_mask: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Global True-count of a per-shard boolean mask: local sum + one scalar
    psum over ``axis_name``.

    The shard_map spelling of the pod bookkeeping scalars
    (``runtime.state.labeled_count`` / ``filled_count`` under a sharded
    mask): the collective moves ONE int32 per shard — never the mask — so
    budget/stop checks stay candidate-window-cheap at pod scale.
    """
    return lax.psum(jnp.sum(local_mask.astype(jnp.int32)), axis_name)


def gather_fills(local_fill: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """All shards' fill watermarks as a replicated ``[S]`` vector.

    One scalar per shard over ICI — the rebalance planner's only global
    input. Every shard computes the identical plan from this vector, so the
    exchange below needs no further coordination round.
    """
    return lax.all_gather(jnp.asarray(local_fill, jnp.int32), axis_name)


def exchange_blocks(block: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Window-sized all-to-all of per-target row blocks.

    ``block`` is ``[S, b, ...]``: slot ``j`` is what this shard sends to
    shard ``j``; the result's slot ``i`` is what shard ``i`` sent here. This
    is the rebalance epoch's ONE bulk collective, and ``b`` is capped at the
    epoch's window-sized block — per-launch traffic is ``S * b`` rows
    regardless of pool scale, which is what keeps the audited program under
    the PR-13 ``collective-bytes-over-budget`` rule.
    """
    return lax.all_to_all(block, axis_name, split_axis=0, concat_axis=0, tiled=True)


def masked_mean(values: jnp.ndarray, mask: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Global mean of ``values`` where ``mask`` is set, across shards.

    Used for pool-level scalar features (LAL f_6; the reference computes these
    with driver-side ``reduce``/``count`` actions, ``active_learner.py:291-296``).
    """
    m = mask.astype(values.dtype)
    total = lax.psum(jnp.sum(values * m), axis_name)
    count = lax.psum(jnp.sum(m), axis_name)
    return total / jnp.maximum(count, 1.0)
