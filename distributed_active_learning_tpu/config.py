"""Experiment configuration dataclasses.

The reference has no config system at all — every tunable is a hardcoded module
constant (window_size / n_samples / n_estimators / beta at
``final_thesis/density_weighting.py:29-33``, per-file window sizes at
``uncertainty_sampling.py:46`` and ``random_sampling.py:47``, dataset switching by
editing commented lines at ``classes/dataset.py:31-40``). This module replaces that
with typed, frozen dataclasses so experiments are reproducible and serializable.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ForestConfig:
    """Random-forest base-learner configuration.

    Mirrors the knobs the reference passes to ``RandomForest.trainClassifier``
    (``final_thesis/uncertainty_sampling.py:71-76``: numTrees, maxDepth=4,
    maxBins=32, 'gini') but with a fixed node budget so the packed on-device
    representation has static shapes across AL rounds (no recompiles).
    """

    n_trees: int = 10
    max_depth: int = 4
    max_bins: int = 32
    criterion: str = "gini"
    # Device evaluation kernel: "gemm" re-expresses traversal as two batched
    # MXU matmuls (ops/trees_gemm.py) — exact, bit-identical to "gather", the
    # default; "pallas" fuses the whole chain in one VMEM-resident kernel
    # (ops/trees_pallas.py, ~2.5x faster scoring on TPU; features compare in
    # bf16, exact for binned/grid data); "gather" keeps the vmapped
    # pointer-chase (ops/trees.py). Deep forests (max_depth > 10)
    # automatically use "gather" (the path matrix grows O(4^depth); see
    # ops.forest_eval.for_kernel). Multi-device meshes evaluate "pallas" as
    # "gemm" (no GSPMD partitioning rule for pallas_call).
    kernel: str = "gemm"
    # Where the forest is *trained*: "host" fits sklearn on the labeled subset
    # (the JVM-fit equivalent); "device" runs the jitted histogram trainer
    # (ops/trees_train.py) — level-wise binned splits like MLlib itself, with
    # the whole round (fit + score + select) staying on the TPU. Device fit
    # uses ``max_bins`` as its histogram resolution.
    fit: str = "host"
    # Static row capacity of the device trainer's labeled window (None = grow
    # to the experiment's label cap). Fixed per experiment so the jitted fit
    # never recompiles as labels accumulate.
    fit_budget: Optional[int] = None
    # Static node budget per tree for the packed representation. A binary tree of
    # depth D has at most 2^(D+1) - 1 nodes; loaders assert fit.
    node_budget: Optional[int] = None
    # Quantized forest storage (ops/trees_train.py::quantize_forest): "bf16"
    # stores thresholds + leaf stats in bfloat16, "int8" additionally rounds
    # classifier leaf probabilities onto a fixed int8 grid — 2-4x less HBM
    # traffic for the bandwidth-bound eval phases, dequantized at the point
    # of use INSIDE the kernels. Device fit only (its thresholds are
    # bf16-snapped bin edges, making bf16 threshold storage lossless —
    # decision paths bit-identical to f32 storage; int8 leaves shift scores
    # by <= 1/254 per probability, tests/test_round_fused.py tolerances).
    quantize: str = "none"
    seed: int = 0

    @property
    def resolved_node_budget(self) -> int:
        if self.node_budget is not None:
            return self.node_budget
        return 2 ** (self.max_depth + 1) - 1


@dataclasses.dataclass(frozen=True)
class StrategyConfig:
    """Query-strategy configuration.

    ``name`` selects from the strategy registry (strategies/__init__.py).
    ``window_size`` is the batch ("window") of points queried per round —
    the reference uses 10/50/100 (``uncertainty_sampling.py:46``) and 1 for the
    OOP single-point mode. ``beta`` weights the density term
    (``density_weighting.py:33``).
    """

    name: str = "uncertainty"
    window_size: int = 10
    beta: float = 1.0
    # Extra per-strategy options (e.g. LAL regressor config, MC-dropout samples).
    options: Mapping[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Dataset selection + preprocessing.

    ``name`` selects from the dataset registry; ``path`` points at on-disk data
    for file-backed datasets (striatum/credit-card formats). ``standardize``
    replicates the reference's StandardScaler(withMean, withStd) step
    (``classes/dataset.py:163-165``).
    """

    name: str = "checkerboard2x2"
    path: Optional[str] = None
    standardize: bool = True
    # None = per-dataset default. True reproduces the reference's quirk of
    # fitting a *separate* scaler on the test set (flagged as an inconsistency
    # at ``classes/dataset.py:268-271``); False uses the train-fitted scaler.
    scale_test_independently: Optional[bool] = None
    n_samples: Optional[int] = None  # subsample pool (density_weighting.py:30)
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class ScenarioConfig:
    """Scenario-engine configuration (scenarios/): perturb the AL loop
    without forking it.

    ``kind`` selects the scenario family; every family rides the SAME
    drivers (``runtime.loop``/``runtime.sweep``) as config + grid axes
    rather than new loops, and ``kind="none"`` (the default) leaves every
    traced program byte-identical to the pre-scenario code — the engine is
    only wired in when a scenario is active.

    - ``"none"``          — the clean pool-based loop (the default).
    - ``"noisy_oracle"``  — the oracle flips each point's label with
      ``flip_prob`` (drawn once per experiment from the scenario seed, so
      repeated queries are consistent) and ABSTAINS on each reveal with
      ``abstain_prob``: abstained picks stay unlabeled and re-enter the
      pool, so budget accounting counts REVEALED labels, never picks (an
      all-abstain oracle never terminates a cell early — ``max_rounds`` is
      therefore required when ``abstain_prob > 0``).
    - ``"cost_budget"``   — per-point labeling costs (synthesized from the
      scenario seed, in ``[1, 1 + cost_spread]``) with budget-constrained
      selection: a greedy knapsack top-k by score-per-cost under a
      per-round spend cap ``cost_budget`` (ops/topk.py
      ``knapsack_top_k``). Nonnegative higher-is-better scores only.
    - ``"rare_event"``    — class-imbalanced hunting: the reported metric
      is recall-at-budget of ``rare_class`` (fraction of the pool's rare
      points labeled so far), computed in-scan and riding
      ``RoundMetrics.rare_recall``.
    - ``"drift"``         — the evaluation stream drifts over rounds: the
      test set is transformed per round index (``drift_kind``
      "mean_shift" or "rotation" at ``drift_rate`` per round,
      data/synthetic.py schedules) before the in-scan accuracy pass — the
      pool is historical data, the incoming traffic moves.
    """

    kind: str = "none"
    # noisy_oracle
    flip_prob: float = 0.0
    abstain_prob: float = 0.0
    # cost_budget
    cost_budget: float = 0.0   # per-round spend cap (> 0 required)
    cost_spread: float = 4.0   # synthetic costs in [1, 1 + cost_spread]
    # rare_event
    rare_class: int = 1
    # drift
    drift_kind: str = "mean_shift"  # or "rotation"
    drift_rate: float = 0.0         # per-round drift magnitude
    # Scenario randomness (flip masks, cost vectors, drift direction) is
    # keyed separately from the experiment seed so a scenario=none cell's
    # PRNG stream is untouched.
    seed: int = 0

    @property
    def active(self) -> bool:
        return self.kind != "none"


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout for the sharded AL round.

    ``data`` shards the pool rows (replaces Spark RDD partitioning of the pool),
    ``model`` shards the tree/ensemble axis (replaces the reference's sequential
    per-tree Spark jobs, ``classes/active_learner.py:169-184``).
    """

    data: int = 1
    model: int = 1

    @property
    def shape(self) -> Tuple[Tuple[str, int], ...]:
        return (("data", self.data), ("model", self.model))


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Streaming AL service knobs (serving/service.py).

    The service holds a slab-paged pool: capacity is allocated in fixed
    ``slab_rows``-row slabs (static shapes per capacity; growth is
    slab-at-a-time) and a dynamic fill watermark tracks how much of it holds
    real points, so per-arrival ingest never changes a program's avals —
    arrivals never recompile. Ingest and scoring both run at fixed widths
    (``ingest_block`` / ``score_width``), padded per call, for the same
    reason.
    """

    slab_rows: int = 1024      # rows per slab (capacity growth quantum)
    ingest_block: int = 64     # static ingest write width (arrivals padded)
    score_width: int = 64      # static scoring batch width (queries padded)
    refit_rounds: int = 4      # AL rounds fused into one re-fit chunk launch
    # Drift-aware re-fit triggers (serving/drift.py), evaluated against the
    # last chunk's in-scan RoundMetrics baseline: a relative shift of the
    # serve-time prediction entropy or of the chunk's selection margin beyond
    # these thresholds dispatches a chunk instead of a fixed round cadence.
    drift_entropy_shift: float = 0.25
    drift_margin_shift: float = 0.5
    # Fresh (ingested, unlabeled) points required before a drift trigger may
    # fire — a re-fit with nothing new to label is wasted work.
    drift_min_fresh: int = 32
    # Staleness backstop: force a re-fit after this many scoring requests
    # without one (0 disables). The cadence-of-last-resort, not the trigger.
    max_staleness: int = 512
    # Pending score requests tolerated before an in-flight re-fit chunk's
    # touchdown is forced (the event loop otherwise polls non-blockingly).
    refit_poll_events: int = 64
    # AOT capacity precompile (serving/tenants.py): when the fill watermark
    # comes within ``precompile_headroom_slabs`` slabs of capacity, a
    # background thread ``lower().compile()``s the NEXT capacity's
    # ingest/chunk/fit programs, so slab growth becomes an executable swap
    # instead of an on-request XLA compile — the ``slab_growth_compile``
    # p99 cause the serve bench tags must vanish after warmup.
    precompile_ahead: bool = True
    precompile_headroom_slabs: float = 1.0
    # Frontend admission cap (serving/frontend.py): queued requests tolerated
    # per tenant before new submissions are refused with AdmissionError —
    # the backpressure signal concurrent clients actually observe.
    max_pending: int = 64
    # Per-tenant SLO class (serving/frontend.py): ``slo_weight`` is the
    # tenant's share of contended dispatch cycles under deficit weighted
    # round-robin — 1.0 (the default) serves the tenant every cycle exactly
    # like the pre-SLO fair rotation; 0.5 every other contended cycle.
    # ``slo_priority`` scales admission under load: a priority-p tenant's
    # effective queue cap is ``max_pending * (1 + p)``, so lower classes
    # shed load (AdmissionError) first.
    slo_weight: float = 1.0
    slo_priority: int = 0
    # Drift-aware bin-edge refresh (serving/tenants.py): the binning is
    # frozen at cold start; when the EMA fraction of ingested feature
    # values landing OUTSIDE the cold-start quantile edges exceeds
    # ``bin_refresh_out_frac`` (with at least ``drift_min_fresh`` fresh
    # points), the service re-quantiles the edges from the current slab,
    # re-codes the pool, rebuilds its fit/chunk programs against the new
    # edges, and bumps the forest fingerprint. In-distribution streams sit
    # near 2/max_bins out-of-range by construction, far under a typical
    # threshold of 0.35. <= 0 (the default) disables — the refresh is
    # opt-in, so services configured before it keep the frozen-edges
    # behavior and its jit-cache/latency profile byte-for-byte.
    bin_refresh_out_frac: float = 0.0
    # Live ops plane (runtime/obs.py): TCP port for the pull-based metrics
    # endpoint — /metrics (Prometheus text), /healthz (event-loop liveness +
    # last-touchdown age), /varz (full JSON snapshot), /flightz (flight-
    # recorder dump over HTTP). 0 (the default) = no listener; the CLI entry
    # points (serving.__main__, bench.py --mode serve-multi, run.py) honor
    # it / the --ops-port flag. The registry FEEDS are always on (cheap
    # host-side ints, bounded histograms); the port only gates the scrape.
    ops_port: int = 0
    # Per-tenant SLO objective (runtime/obs.py SLOTracker): a query is GOOD
    # when it succeeds AND answers within slo_latency_ms; the tracker keeps
    # the lifetime compliance ratio good/total and multi-window (1m/5m/1h)
    # burn rates bad_frac / (1 - slo_target) — the SRE-workbook alerting
    # form, surfaced as /metrics gauges, `slo` JSONL events, the service
    # summary, and the serve-multi bench's `slo_compliance` key. <= 0 (the
    # default) disables SLO accounting entirely.
    slo_latency_ms: float = 0.0
    slo_target: float = 0.99
    # Burn-rate-driven admission (serving/frontend.py): when the tenant's
    # 5-minute burn rate (SLOTracker — bad_frac / error budget; 1.0 = the
    # budget is being spent exactly at the sustainable rate) reaches this
    # threshold, NEW score submissions are shed at admission with
    # AdmissionError — the SLO is already lost for this window, so refusing
    # early keeps the doomed tenant's queue from delaying healthy ones.
    # Ingest is never shed (fresh data is how a burning tenant recovers).
    # <= 0 (the default) disables shedding; independent of the always-on
    # dispatch deprioritization, which scales a burning tenant's effective
    # slo_weight by 1 / (1 + burn) once burn >= 1.
    burn_shed_threshold: float = 0.0


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    """Top-level AL experiment: dataset + model + strategy + loop controls."""

    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    forest: ForestConfig = dataclasses.field(default_factory=ForestConfig)
    strategy: StrategyConfig = dataclasses.field(default_factory=StrategyConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    # Scenario engine (scenarios/): noisy oracles, cost-budgeted selection,
    # rare-event hunting, drifting evaluation streams — perturbations of the
    # SAME loop, validated at run start (scenarios.validate_scenario) and
    # inactive ("none") by default, in which case no traced program changes.
    scenario: ScenarioConfig = dataclasses.field(default_factory=ScenarioConfig)
    # Number of initially-labeled points (Dataset.setStartState nStart,
    # classes/dataset.py:56). The reference seeds 1 positive + 1 negative + extras.
    n_start: int = 10
    # Stop when this many points are labeled, or pool exhausted (None = exhaust).
    label_budget: Optional[int] = None
    max_rounds: Optional[int] = None
    # Rounds fused into ONE jitted lax.scan launch when the whole round is
    # device-resident (ForestConfig.fit == "device"): the host touches down
    # only every rounds_per_launch rounds to append records/log/checkpoint,
    # cutting per-round host syncs from 3 to <= 3/K on launch-latency-bound
    # rigs. Purely a performance knob — stopping stays exact (rounds past the
    # label budget are in-scan no-ops) and results are identical to the
    # per-round driver. Silently falls back to the per-round path for host
    # fit or when a Debugger wants per-phase timings (runtime/loop.py).
    rounds_per_launch: int = 1
    # Chunk launches allowed in flight at once (runtime/pipeline.py): with
    # the default 2 the driver dispatches chunk N+1 from device-resident
    # state before chunk N's host touchdown (record append / logging /
    # checkpoint) runs, hiding the touchdown behind device execution; one
    # speculative chunk may overrun the stop point as masked no-ops, so
    # results stay bit-identical to depth 1 (today's strict serial order,
    # the exact fallback used for host fit / --phase-detail). Performance-
    # only, like rounds_per_launch; takes effect when rounds_per_launch > 1.
    pipeline_depth: int = 2
    # Batched experiment sweep width (runtime/sweep.py): values > 1 run that
    # many seeds (cfg.seed, cfg.seed+1, ...) as ONE vmapped launch stream —
    # the chunk program batched over a leading experiment axis sharing the
    # pool, with per-seed results bit-identical to serial runs. Performance-
    # only like rounds_per_launch; run.py routes --sweep-seeds N > 1 to
    # runtime.sweep.run_sweep (host fit / --phase-detail fall back to N
    # serial runs). Excluded from checkpoint identity; sweep checkpoints
    # carry their own seed-vector fingerprint.
    sweep_seeds: int = 1
    # Stream per-round events to the MetricsWriter from INSIDE a running
    # chunk via jax.debug.callback ("round_stream" JSONL events), instead of
    # only at chunk touchdowns. Off by default: the flag adds a host callback
    # to the traced chunk program, and the zero-overhead fast path must stay
    # untouched unless explicitly asked for.
    stream_round_events: bool = False
    # Round megakernel (ops/round_fused.py): fuse forest eval -> acquisition
    # score -> top-k selection into ONE pass over the pool slab — a pallas
    # megakernel for kernel="pallas" (votes accumulate in VMEM, per-tile
    # top-k on the last tree tile; neither the [pool, trees] vote matrix nor
    # the score vector lands in HBM), an XLA lax.map stream of exact GEMM
    # tile bodies for kernel="gemm". Bit-identical to the unfused path
    # (tests/test_round_fused.py pins CPU + the 4x2 mesh). Opt-in and loudly
    # validated: only the vote-fraction strategies fuse
    # (ops.round_fused.FUSED_STRATEGIES), the fit must be on device, binary
    # pools only, and RoundMetrics are refused (they need the full score
    # vector the megakernel exists to avoid materializing).
    fused_round: bool = False
    seed: int = 0
    # Observability
    # Compute per-round RoundMetrics (runtime/telemetry.py) on device and
    # attach them to records: selection-score summary, margin to the best
    # unpicked candidate, mean pool entropy, picked-class histogram, labeled
    # fraction. In the scan-fused driver they ride the existing chunk ys —
    # no extra host syncs; a MetricsWriter passed to run_experiment enables
    # this implicitly.
    collect_metrics: bool = False
    # Emit per-program roofline attribution (analysis/roofline.py) into the
    # metrics stream at run end: the launched chunk program's static
    # cost_analysis (flops, bytes accessed) joined with its measured launch
    # seconds into achieved FLOP/s, bandwidth, MFU, and a compute-vs-
    # bandwidth bound verdict (`roofline` JSONL events). Costs one extra AOT
    # compile of the chunk program AFTER the run finishes; no effect without
    # a MetricsWriter or on the per-round fallback path.
    roofline: bool = False
    log_every: int = 1
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0  # 0 = disabled
    results_path: Optional[str] = None


def asdict(cfg: Any) -> dict:
    """Serialize any config dataclass to a plain dict (for checkpoint metadata)."""
    return dataclasses.asdict(cfg)
